"""Peer-to-peer elastic restore: the replacement rank's shards come from
surviving hosts' memory, not from Orbax storage.

Why: after a single-host failure the survivors still hold every replicated
shard of the model/optimizer state — restoring the replacement from storage
is why ``elastic_restore_seconds_at_scale`` was 105.5 s (BENCH_r05) while
the single-host path was 8 s. ElasWave's in-memory state redistribution and
the Orbax distributed-checkpointing paper (PAPERS.md) are the blueprints.

The pieces, in data-flow order:

- :class:`PeerStateStore` (worker): at every checkpoint boundary the live
  state is mirrored leaf-by-leaf into a host-RAM staging directory the
  agent owns (the same bytes the Orbax save just staged, so peer step N
  and Orbax step N are the SAME consistent cut). The manifest — step,
  per-shard dtype/shape/CRC, the data-position state — is written last,
  atomically, so a SIGKILL mid-stage leaves the previous step intact.
- :class:`PeerDonorServer` (agent): a tiny length-prefixed TCP protocol
  serving staged shards. It lives in the AGENT process, so it survives the
  worker restarts a membership change forces — that is what makes the
  staged bytes "surviving HBM" from the replacement's point of view.
- The master's restore plan (master/rendezvous.py ``compute_restore_plan``)
  maps each staged shard key to a surviving donor, stamped with the
  ``world_epoch`` so a second failure mid-transfer invalidates the plan.
- :class:`PeerRestorer` (worker): plan → parallel shard fetch (local cache
  hits short-circuit the network) → epoch re-validation → device arrays
  via the resharding primitive (parallel/sharding.sharded_from_host).
  Shards no surviving replica holds degrade shard-wise to Orbax at the
  SAME step (``mixed``); anything less consistent falls back wholesale
  (``orbax``) — never a silent zero-init.
"""

from __future__ import annotations

import json
import os
import shutil
import socket
import socketserver
import threading
import time
import zlib
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from dlrover_tpu import obs
from dlrover_tpu.common.config import Context
from dlrover_tpu.common.constants import NodeEnv
from dlrover_tpu.common.log import default_logger as logger

MANIFEST = "manifest.json"
# stage dirs retained beyond the current one: a donor restaging a newer
# step must not yank the files a plan computed moments ago points at
_RETAIN_STAGES = 2
_HEADER_LIMIT = 1 << 20


# ---------------------------------------------------------------------------
# shard keys + host copies
# ---------------------------------------------------------------------------


def shard_items(tree: Any) -> List[Tuple[str, Any]]:
    """(key, leaf) pairs in canonical tree order; the key is the leaf's
    path string — identical on the staging and restoring side as long as
    both hold the same state structure (they do: it is the same model)."""
    import jax

    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [(jax.tree_util.keystr(path), leaf) for path, leaf in flat]


def host_copy(leaf: Any) -> Optional[np.ndarray]:
    """Device leaf → host ndarray, or None when this process cannot see
    the whole leaf (sharded across hosts with no local replica — exactly
    the shards that die with a host and force the Orbax fallback)."""
    import jax

    if isinstance(leaf, jax.Array):
        if getattr(leaf, "is_fully_replicated", False):
            try:
                return np.asarray(leaf.addressable_data(0))
            except Exception:  # noqa: BLE001 — backend-specific failures
                return None
        if getattr(leaf, "is_fully_addressable", True):
            return np.asarray(leaf)
        return None
    return np.asarray(leaf)


def _atomic_write(path: str, payload: bytes) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(payload)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def load_manifest(directory: str) -> Optional[Dict[str, Any]]:
    """The staged manifest, or None when absent/torn (a torn stage left
    the previous manifest in place — readers never see half a step)."""
    try:
        with open(os.path.join(directory, MANIFEST)) as f:
            manifest = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if not isinstance(manifest, dict) or "shards" not in manifest:
        return None
    return manifest


def load_stage_manifest(directory: str, step: int
                        ) -> Optional[Dict[str, Any]]:
    """The manifest for one SPECIFIC staged step: the current one when
    it matches, else the per-stage copy inside the retained stage dir —
    a donor restaging a newer step mid-transfer must keep serving the
    step an in-flight plan was computed for (that is what the retention
    window exists for)."""
    manifest = load_manifest(directory)
    if manifest is not None and int(manifest.get("step", -1)) == step:
        return manifest
    return load_manifest(os.path.join(directory, f"stage-{step}"))


def manifest_summary(directory: str
                     ) -> Tuple[int, List[str], int]:
    """(step, shard keys, total bytes) of the staged manifest;
    (-1, [], 0) when nothing usable is staged."""
    manifest = load_manifest(directory)
    if manifest is None:
        return -1, [], 0
    shards = manifest.get("shards", {})
    total = sum(int(s.get("nbytes", 0)) for s in shards.values())
    return int(manifest.get("step", -1)), sorted(shards), total


def read_local_shard(directory: str, manifest: Dict[str, Any],
                     key: str) -> Optional[bytes]:
    """Read + CRC-verify one staged shard; None on any mismatch."""
    meta = manifest.get("shards", {}).get(key)
    if meta is None:
        return None
    try:
        path = os.path.join(directory, manifest.get("dir", ""),
                            meta["file"])
        with open(path, "rb") as f:
            data = f.read()
    except (OSError, KeyError):
        return None
    if (len(data) != int(meta.get("nbytes", -1))
            or (zlib.crc32(data) & 0xFFFFFFFF) != int(meta.get("crc32",
                                                               -1))):
        return None
    return data


# ---------------------------------------------------------------------------
# worker-side staging
# ---------------------------------------------------------------------------


class PeerStateStore:
    """Host-RAM mirror of the live state, staged at checkpoint
    boundaries so the bytes outlive the worker process. Single-writer by
    contract (the step loop); readers (the donor server, a respawned
    worker) only ever see a complete step through the atomic manifest."""

    def __init__(self, directory: str):
        self._dir = directory
        # serializes deferred stage writes (joined before the next
        # stage, on flush, and by readers-in-process via flush)
        self._writer: Optional[threading.Thread] = None
        os.makedirs(directory, exist_ok=True)

    @classmethod
    def from_env(cls) -> Optional["PeerStateStore"]:
        directory = os.environ.get(NodeEnv.PEER_CACHE_DIR, "")
        if not directory or not Context.singleton().peer_restore_enabled:
            return None
        return cls(directory)

    @property
    def directory(self) -> str:
        return self._dir

    def stage(self, step: int, state: Any,
              data_state: Optional[Dict[str, Any]] = None,
              defer_write: bool = False) -> bool:
        """Mirror ``state`` (exact dtypes: the live-precision cut — when
        the checkpoint itself stores exact dtypes a peer restore is
        bitwise identical to the Orbax restore of the same step; with a
        quantized checkpoint the peer copy is strictly HIGHER fidelity
        than the storage path) into the cache.

        The device→host copy always runs on the caller (the arrays may
        be donated away by the next train step); with ``defer_write``
        the file writes + CRCs happen on a background thread so the
        step loop only pays the copy. Returns whether anything was
        staged (dispatched, when deferred); never raises into the step
        loop."""
        try:
            host_items: List[Tuple[str, np.ndarray]] = []
            skipped: List[str] = []
            for key, leaf in shard_items(state):
                arr = host_copy(leaf)
                if arr is None:
                    # no local replica of this shard: it dies with the
                    # host — the restore plan will route it to Orbax
                    skipped.append(key)
                    continue
                host_items.append((key, arr))
            if not host_items:
                return False
            self.flush()   # serialize with a previous deferred write
            if not defer_write:
                return self._write_stage(step, host_items, skipped,
                                         dict(data_state or {}))
            self._writer = threading.Thread(
                target=self._write_stage,
                args=(step, host_items, skipped, dict(data_state or {})),
                daemon=True, name=f"peer-stage-{step}")
            self._writer.start()
            return True
        except Exception:  # noqa: BLE001 — staging is an optimization
            logger.warning("peer-state staging at step %d failed", step,
                           exc_info=True)
            return False

    def flush(self) -> None:
        """Join an in-flight deferred stage write (readers in the same
        process call this before trusting the manifest)."""
        writer = self._writer
        if writer is not None and writer.is_alive():
            writer.join()
        self._writer = None

    def _write_stage(self, step: int, host_items, skipped,
                     data_state: Dict[str, Any]) -> bool:
        stage_name = f"stage-{step}"
        tmp = os.path.join(self._dir, f"{stage_name}.tmp")
        final = os.path.join(self._dir, stage_name)
        try:
            shutil.rmtree(tmp, ignore_errors=True)
            os.makedirs(tmp)
            shards: Dict[str, Dict[str, Any]] = {}
            for i, (key, arr) in enumerate(host_items):
                data = np.ascontiguousarray(arr).tobytes()
                fname = f"leaf-{i}.bin"
                with open(os.path.join(tmp, fname), "wb") as f:
                    f.write(data)
                shards[key] = {
                    "file": fname,
                    "dtype": str(arr.dtype),
                    "shape": list(arr.shape),
                    "nbytes": len(data),
                    "crc32": zlib.crc32(data) & 0xFFFFFFFF,
                }
            manifest = {
                "step": int(step),
                "dir": stage_name,
                "staged_at": time.time(),
                "data_state": data_state,
                "shards": shards,
                "skipped": skipped,
            }
            # the per-stage copy rides INSIDE the dir (atomic with the
            # rename): the donor keeps serving this step after a newer
            # stage overwrites the top-level manifest
            with open(os.path.join(tmp, MANIFEST), "w") as f:
                json.dump(manifest, f)
            shutil.rmtree(final, ignore_errors=True)
            os.replace(tmp, final)
            _atomic_write(os.path.join(self._dir, MANIFEST),
                          json.dumps(manifest).encode())
            self._prune(keep=stage_name)
            return True
        except Exception:  # noqa: BLE001 — staging is an optimization
            logger.warning("peer-state staging at step %d failed", step,
                           exc_info=True)
            shutil.rmtree(tmp, ignore_errors=True)
            return False

    def _prune(self, keep: str) -> None:
        """Drop old stage dirs beyond the retention window (the newest
        few stay so an in-flight transfer keyed on the previous step is
        not yanked mid-read)."""
        try:
            stages = sorted(
                (name for name in os.listdir(self._dir)
                 if name.startswith("stage-")
                 and not name.endswith(".tmp")),
                key=lambda n: int(n.split("-")[1])
                if n.split("-")[1].isdigit() else -1)
        except OSError:
            return
        for name in stages[:-_RETAIN_STAGES]:
            if name != keep:
                shutil.rmtree(os.path.join(self._dir, name),
                              ignore_errors=True)


# ---------------------------------------------------------------------------
# donor-side server (runs in the agent: survives worker restarts)
# ---------------------------------------------------------------------------


class _DonorHandler(socketserver.StreamRequestHandler):
    timeout = 30.0

    def handle(self) -> None:  # one connection, many requests
        while True:
            try:
                line = self.rfile.readline(_HEADER_LIMIT)
            except OSError:
                return
            if not line.strip():
                return
            try:
                request = json.loads(line)
            except json.JSONDecodeError:
                self._reply({"ok": False, "error": "bad request"})
                return
            if not self._serve(request):
                return

    def _reply(self, header: Dict[str, Any],
               payload: bytes = b"") -> bool:
        try:
            self.wfile.write(json.dumps(header).encode() + b"\n")
            if payload:
                self.wfile.write(payload)
            self.wfile.flush()
            return True
        except OSError:
            return False

    def _serve(self, request: Dict[str, Any]) -> bool:
        cache_dir = self.server.cache_dir  # type: ignore[attr-defined]
        op = request.get("op", "")
        if op == "manifest":
            # step-addressed when given (a plan's step survives a donor
            # restaging a newer one), the current stage otherwise
            step = request.get("step")
            manifest = (load_stage_manifest(cache_dir, int(step))
                        if step is not None else load_manifest(cache_dir))
            payload = json.dumps(manifest or {}).encode()
            return self._reply({"ok": manifest is not None,
                                "nbytes": len(payload)}, payload)
        if op != "shard":
            return self._reply({"ok": False, "error": f"bad op {op!r}"})
        key = str(request.get("key", ""))
        step = int(request.get("step", -1))
        manifest = load_stage_manifest(cache_dir, step)
        if manifest is None:
            return self._reply({
                "ok": False, "error": f"step {step} not staged"})
        data = read_local_shard(cache_dir, manifest, key)
        if data is None:
            return self._reply({"ok": False,
                                "error": f"shard {key!r} unavailable"})
        meta = manifest["shards"][key]
        # byte-range serving (the resharding-migration stripe mode,
        # master/rendezvous.py compute_restore_plan(stripe=True)): the
        # receiver reassembles ranges from several donors and verifies
        # the FULL-shard CRC carried in every range header. The whole
        # shard was CRC-verified by read_local_shard above, so a range
        # of it is trustworthy too.
        offset = int(request.get("offset", 0) or 0)
        length = request.get("length")
        if offset or length is not None:
            end = (offset + int(length)) if length is not None \
                else len(data)
            if not (0 <= offset <= end <= len(data)):
                return self._reply({
                    "ok": False,
                    "error": f"bad range [{offset}, {end}) of "
                             f"{len(data)}"})
            data = data[offset:end]
        return self._reply({"ok": True, "nbytes": len(data),
                            "crc32": meta["crc32"],
                            "total_nbytes": meta["nbytes"],
                            "dtype": meta["dtype"],
                            "shape": meta["shape"]}, data)


class _DonorTCPServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class PeerDonorServer:
    """Serves the local peer-state cache to replacement ranks. Owned by
    the agent so a worker restart (the thing every membership change
    does) never interrupts an in-flight donation."""

    def __init__(self, cache_dir: str, port: Optional[int] = None):
        self._cache_dir = cache_dir
        self._port = (port if port is not None
                      else Context.singleton().peer_donor_port)
        self._server: Optional[_DonorTCPServer] = None
        self._thread: Optional[threading.Thread] = None
        self.addr = ""

    def start(self) -> str:
        from dlrover_tpu.common.comm import local_ip

        server = _DonorTCPServer(("", self._port), _DonorHandler)
        server.cache_dir = self._cache_dir  # type: ignore[attr-defined]
        self._server = server
        self._thread = threading.Thread(
            target=server.serve_forever, kwargs={"poll_interval": 0.2},
            daemon=True, name="peer-donor")
        self._thread.start()
        self.addr = f"{local_ip()}:{server.server_address[1]}"
        logger.info("peer donor serving %s at %s", self._cache_dir,
                    self.addr)
        return self.addr

    def stop(self) -> None:
        # idempotent under concurrent callers: the agent's run-loop
        # finally and an external shutdown() may both land here — swap
        # the fields out first so only one caller tears each down
        server, self._server = self._server, None
        thread, self._thread = self._thread, None
        if server is not None:
            server.shutdown()
            server.server_close()
        if thread is not None:
            thread.join(timeout=5.0)


# ---------------------------------------------------------------------------
# receiver-side fetch
# ---------------------------------------------------------------------------


class _DonorConnection:
    """One persistent connection to a donor; shard requests ride it
    sequentially (the per-donor fetch thread is the only user)."""

    def __init__(self, addr: str, timeout_s: float):
        host, port = addr.rsplit(":", 1)
        self._timeout_s = timeout_s
        self._sock = socket.create_connection((host, int(port)),
                                              timeout=timeout_s)
        self._file = self._sock.makefile("rb")

    def request(self, payload: Dict[str, Any], deadline: float = 0.0
                ) -> Tuple[Dict[str, Any], bytes]:
        """One request/response. ``deadline`` (unix ts) hard-bounds the
        WHOLE body read — a trickling donor must not extend the restore
        past the transfer budget one recv-window at a time (the Orbax
        fallback is waiting)."""
        self._sock.sendall(json.dumps(payload).encode() + b"\n")
        header = json.loads(self._file.readline(_HEADER_LIMIT))
        nbytes = int(header.get("nbytes", 0))
        if not header.get("ok") or not nbytes:
            return header, b""
        chunks: List[bytes] = []
        read = 0
        while read < nbytes:
            if deadline:
                remaining = deadline - time.time()
                if remaining <= 0:
                    raise OSError(
                        f"peer transfer deadline exceeded mid-shard "
                        f"({read}/{nbytes} bytes)")
                self._sock.settimeout(min(self._timeout_s, remaining))
            chunk = self._file.read(min(1 << 20, nbytes - read))
            if not chunk:
                raise OSError(f"short read ({read}/{nbytes})")
            chunks.append(chunk)
            read += len(chunk)
        return header, b"".join(chunks)

    def close(self) -> None:
        try:
            self._file.close()
            self._sock.close()
        except OSError:
            pass


def fetch_manifest(addr: str, timeout_s: float = 10.0,
                   step: Optional[int] = None
                   ) -> Optional[Dict[str, Any]]:
    """One donor's staged manifest (step + data-position state); with
    ``step``, the manifest of that specific retained stage."""
    request = {"op": "manifest"}
    if step is not None:
        request["step"] = int(step)
    try:
        conn = _DonorConnection(addr, timeout_s)
        try:
            header, payload = conn.request(request)
        finally:
            conn.close()
        if not header.get("ok"):
            return None
        return json.loads(payload)
    except (OSError, ValueError, json.JSONDecodeError):
        return None


def _verify(data: bytes, header: Dict[str, Any],
            expected_nbytes: int) -> bool:
    return (len(data) == expected_nbytes
            and int(header.get("nbytes", -1)) == expected_nbytes
            and (zlib.crc32(data) & 0xFFFFFFFF)
            == int(header.get("crc32", -1)))


def _stripe_ranges(nbytes: int, parts: int) -> List[Tuple[int, int]]:
    """Split ``nbytes`` into ``parts`` contiguous (offset, length)
    ranges — the byte-level "who sends which shard slice" of the
    resharding-migration stripe mode. Deterministic, covers every byte
    exactly once, tolerates parts > nbytes (empty tail ranges are
    dropped)."""
    parts = max(1, min(parts, nbytes)) if nbytes > 0 else 1
    base, extra = divmod(nbytes, parts)
    ranges: List[Tuple[int, int]] = []
    offset = 0
    for i in range(parts):
        length = base + (1 if i < extra else 0)
        if length <= 0:
            continue
        ranges.append((offset, length))
        offset += length
    return ranges


def fetch_shards(
    plan: Dict[str, Any],
    wanted: Dict[str, int],
    local_cache_dir: str = "",
    deadline: float = 0.0,
) -> Tuple[Dict[str, bytes], Dict[str, int], List[str]]:
    """Fetch the wanted shard bytes per the plan.

    ``wanted``: key → expected byte count (from the abstract state, the
    authority on dtype/shape). Local cache hits (a survivor restoring on
    its own host) never touch the network. Returns (key → bytes,
    per-donor byte table — "local" for cache hits, missing keys). A
    failed/timed-out/corrupt shard is simply missing: the caller decides
    between the shard-wise Orbax fallback and a wholesale one.

    Striped entries (plan mode "stripe": ``{"ranks": [...], "addrs":
    [...]}``) split the shard's bytes into contiguous ranges fetched
    from several donors in parallel — the resharding migration's
    transfer primitive. The reassembled shard is verified against the
    FULL-shard CRC every range header carries; any failed range fails
    the whole key (missing, never wrong)."""
    step = int(plan.get("step", -1))
    entries = plan.get("entries", {})
    got: Dict[str, bytes] = {}
    donor_bytes: Dict[str, int] = {}
    # addr -> [(key, offset, length or None=whole)]
    remote: Dict[str, List[Tuple[str, int, Optional[int]]]] = {}
    # striped reassembly state: key -> {offset: bytes}, key -> crc set
    striped_parts: Dict[str, Dict[int, bytes]] = {}
    striped_crcs: Dict[str, set] = {}
    striped_expected: Dict[str, int] = {}   # number of ranges issued
    missing: List[str] = []
    local_manifest = (load_stage_manifest(local_cache_dir, step)
                      if local_cache_dir else None)
    for key, nbytes in wanted.items():
        entry = entries.get(key)
        if local_manifest is not None:
            data = read_local_shard(local_cache_dir, local_manifest, key)
            if data is not None and len(data) == nbytes:
                got[key] = data
                donor_bytes["local"] = (donor_bytes.get("local", 0)
                                        + len(data))
                continue
        if not entry:
            missing.append(key)
            continue
        addrs = entry.get("addrs") or []
        if len(addrs) > 1 and nbytes > 0:
            ranges = _stripe_ranges(nbytes, len(addrs))
            striped_expected[key] = len(ranges)
            striped_parts[key] = {}
            striped_crcs[key] = set()
            for addr, (offset, length) in zip(addrs, ranges):
                remote.setdefault(addr, []).append((key, offset,
                                                    length))
            continue
        addr = entry.get("addr") or (addrs[0] if addrs else "")
        if not addr:
            missing.append(key)
            continue
        remote.setdefault(addr, []).append((key, 0, None))

    # collected under `lock` by the per-donor threads
    lock = threading.Lock()
    failed_keys: set = set()

    def _fetch_from(addr: str) -> Tuple[Dict[str, bytes], List[str]]:
        fetched: Dict[str, bytes] = {}
        failed: List[str] = []
        work = remote[addr]
        conn = None
        done: List[Tuple] = []
        try:
            conn = _DonorConnection(addr, timeout_s=30.0)
            for item in work:
                key, offset, length = item
                if deadline and time.time() > deadline:
                    break
                request = {"op": "shard", "key": key, "step": step}
                if length is not None:
                    request["offset"] = offset
                    request["length"] = length
                try:
                    header, data = conn.request(request,
                                                deadline=deadline)
                except (OSError, ValueError):
                    # connection died mid-stream: re-dial once for the
                    # remaining keys of this donor (unless the budget
                    # itself is what killed it)
                    if deadline and time.time() > deadline:
                        raise
                    conn.close()
                    conn = _DonorConnection(addr, timeout_s=30.0)
                    header, data = conn.request(request,
                                                deadline=deadline)
                done.append(item)
                if length is None:
                    if header.get("ok") and _verify(data, header,
                                                    wanted[key]):
                        fetched[key] = data
                    else:
                        failed.append(key)
                    continue
                # striped range: stash the part; the reassembly (and
                # the full-shard CRC check) happens once every donor
                # thread finished
                if header.get("ok") and len(data) == length:
                    with lock:
                        striped_parts[key][offset] = data
                        striped_crcs[key].add(
                            int(header.get("crc32", -1)))
                else:
                    with lock:
                        failed_keys.add(key)
        except (OSError, ValueError) as e:
            logger.warning("peer fetch from %s failed: %s", addr, e)
        finally:
            if conn is not None:
                conn.close()
        # anything not completed on this donor: whole keys fail here,
        # striped keys fail via failed_keys
        for item in work:
            if item in done:
                continue
            key, offset, length = item
            if length is None:
                if key not in fetched and key not in failed:
                    failed.append(key)
            else:
                with lock:
                    failed_keys.add(key)
        return fetched, failed

    if remote:
        with ThreadPoolExecutor(
                max_workers=min(8, len(remote))) as pool:
            for addr, (fetched, failed) in zip(
                    remote, pool.map(_fetch_from, list(remote))):
                got.update(fetched)
                if fetched:
                    donor_bytes[addr] = sum(len(d)
                                            for d in fetched.values())
                missing.extend(failed)
    # striped reassembly: every range present, the donors' full-shard
    # CRCs agree, and the assembled bytes re-hash to that CRC — a
    # failed/disagreeing stripe makes the key MISSING, never wrong
    for key, parts in striped_parts.items():
        nbytes = wanted[key]
        crcs = striped_crcs.get(key) or set()
        if (key in failed_keys
                or len(parts) != striped_expected.get(key, -1)
                or len(crcs) != 1):
            missing.append(key)
            continue
        assembled = b"".join(parts[off] for off in sorted(parts))
        expected_crc = next(iter(crcs))
        if (len(assembled) != nbytes
                or (zlib.crc32(assembled) & 0xFFFFFFFF) != expected_crc):
            missing.append(key)
            continue
        got[key] = assembled
        for addr, work in remote.items():
            contributed = sum(length or 0 for k, off, length in work
                              if k == key and off in parts)
            if contributed:
                donor_bytes[addr] = (donor_bytes.get(addr, 0)
                                     + contributed)
    return got, donor_bytes, missing


# ---------------------------------------------------------------------------
# the worker-side restore orchestration
# ---------------------------------------------------------------------------


class PeerRestorer:
    """Plan → transfer → validate → assemble, with the shard-wise Orbax
    fallback. One instance per ElasticTrainLoop."""

    def __init__(self, client=None, cache: Optional[PeerStateStore] = None,
                 plan_file: str = ""):
        self._client = client
        self._cache = cache
        self._plan_file = (plan_file
                           or os.environ.get(NodeEnv.RESTORE_PLAN_FILE,
                                             ""))
        # resharding-migration mode (set by the loop when a parallelism
        # re-plan changed the target sharding): RPC plans stripe each
        # shard's byte ranges across every same-step holder
        self.stripe = False

    @classmethod
    def from_env(cls, client=None) -> Optional["PeerRestorer"]:
        if not Context.singleton().peer_restore_enabled:
            return None
        cache = PeerStateStore.from_env()
        plan_file = os.environ.get(NodeEnv.RESTORE_PLAN_FILE, "")
        if client is None and cache is None and not plan_file:
            return None
        return cls(client=client, cache=cache, plan_file=plan_file)

    @property
    def cache(self) -> Optional[PeerStateStore]:
        return self._cache

    # -- plan acquisition ---------------------------------------------------
    def _fetch_plan(self) -> Optional[Dict[str, Any]]:
        """Freshest plan first: the master RPC (recomputed now), then
        the plan shipped in the agent's join result, then — standalone,
        no master — a purely local pseudo-plan over this host's cache."""
        if self._client is not None:
            try:
                # stripe passed only when armed: client wrappers/shims
                # predating the kwarg keep working on the default path
                plan = (self._client.get_restore_plan(stripe=True)
                        if self.stripe
                        else self._client.get_restore_plan())
                if plan:
                    return plan
            except Exception:  # noqa: BLE001 — degrade to the file plan
                logger.warning("restore-plan RPC failed; using the "
                               "join-result plan", exc_info=True)
        if self._plan_file:
            try:
                with open(self._plan_file) as f:
                    plan = json.load(f)
                if isinstance(plan, dict) and plan.get("entries"):
                    return plan
            except (OSError, json.JSONDecodeError):
                pass
        if self._cache is not None:
            step, keys, _ = manifest_summary(self._cache.directory)
            if step >= 0:
                # local-only: epoch -1 disables the staleness check
                # (there is no master to have recomputed membership)
                return {"epoch": -1, "step": step,
                        "entries": {key: {"rank": -1, "addr": ""}
                                    for key in keys}}
        return None

    def _current_epoch(self) -> Optional[int]:
        if self._client is None:
            return None
        try:
            return self._client.get_restore_epoch()
        except Exception:  # noqa: BLE001 — treat as unverifiable
            return None

    # -- the restore --------------------------------------------------------
    def restore(self, abstract_state: Any, checkpointer=None,
                timings: Optional[Dict[str, float]] = None,
                _retry: bool = True
                ) -> Optional[Tuple[Any, Dict[str, Any], int, str]]:
        """Restore from surviving peers. Returns (state, data_state,
        step, source) with source ``"peer"`` or ``"mixed"``; None means
        the caller must take the full Orbax path (no plan, no donors, a
        newer Orbax step, or an unrecoverably stale plan)."""
        timings = timings if timings is not None else {}
        recorder = obs.get_flight_recorder()
        t0 = time.monotonic()
        plan = self._fetch_plan()
        timings["peer_plan_s"] = round(time.monotonic() - t0, 3)
        if not plan or not plan.get("entries"):
            return None
        step = int(plan.get("step", -1))
        if step < 0:
            return None
        latest = None
        if checkpointer is not None:
            try:
                latest = checkpointer.latest_step()
            except Exception:  # noqa: BLE001 — storage may be torn
                latest = None
        if latest is not None and latest > step:
            # storage moved past the staged state (e.g. a final commit
            # landed after the last stage): peers would rewind the job
            logger.warning(
                "peer restore: Orbax step %d is newer than the staged "
                "step %d; taking the storage path", latest, step)
            recorder.record_event("peer_restore_skipped", step=step,
                                  orbax_step=latest, reason="stale-stage")
            return None
        wanted: Dict[str, int] = {}
        abstract_by_key: Dict[str, Any] = {}
        for key, leaf in shard_items(abstract_state):
            abstract_by_key[key] = leaf
            wanted[key] = int(np.prod(leaf.shape)
                              * np.dtype(leaf.dtype).itemsize)
        deadline = time.time() + Context.singleton().peer_restore_timeout_s
        t0 = time.monotonic()
        local_dir = self._cache.directory if self._cache else ""
        with obs.span("restore_peer_transfer",
                      {"step": step,
                       "shards": len(wanted)}) as transfer_span:
            got, donor_bytes, failed = fetch_shards(
                plan, wanted, local_cache_dir=local_dir,
                deadline=deadline)
            transfer_s = time.monotonic() - t0
            total_bytes = sum(len(d) for d in got.values())
            transfer_span.set_attr("bytes", total_bytes)
            transfer_span.set_attr("donors", len(donor_bytes))
            if transfer_s > 0:
                transfer_span.set_attr(
                    "bandwidth_mbps",
                    round(total_bytes / (1 << 20) / transfer_s, 2))
        timings["peer_transfer_s"] = round(transfer_s, 3)
        timings["peer_bytes"] = float(total_bytes)
        if transfer_s > 0 and total_bytes > 0:
            timings["peer_bandwidth_mbps"] = round(
                total_bytes / (1 << 20) / transfer_s, 2)
        missing = sorted(set(wanted) - set(got))
        # the staleness guard: a second failure that mutated membership
        # after the plan was computed invalidates it — shards fetched
        # from a donor that is now dead/draining may be about to vanish
        # (or already reflect a world this rank is no longer part of).
        # Checked AFTER the transfer, immediately before commit.
        plan_epoch = int(plan.get("epoch", -1))
        if plan_epoch >= 0:
            current = self._current_epoch()
            if current is not None and current != plan_epoch:
                recorder.record_event(
                    "restore_plan_stale", plan_epoch=plan_epoch,
                    current_epoch=current, step=step)
                obs.get_registry().counter(
                    "dlrover_tpu_restore_plan_stale_total",
                    "Restore plans rejected by the world-epoch "
                    "staleness guard").inc()
                logger.warning(
                    "restore plan stale (epoch %d -> %d): %s", plan_epoch,
                    current, "recomputing" if _retry else "falling back "
                    "to Orbax")
                if _retry:
                    return self.restore(abstract_state, checkpointer,
                                        timings, _retry=False)
                return None
        data_state = self._data_state(plan, step, donor_bytes,
                                      checkpointer)
        if missing:
            return self._finish_mixed(
                abstract_state, abstract_by_key, got, missing, step,
                data_state, checkpointer, donor_bytes, timings)
        state = self._assemble(abstract_state, abstract_by_key, got)
        self._record(step, "peer", donor_bytes, missing=0,
                     total_bytes=total_bytes, transfer_s=transfer_s)
        return state, data_state, step, "peer"

    def _data_state(self, plan: Dict[str, Any], step: int,
                    donor_bytes: Dict[str, int], checkpointer
                    ) -> Dict[str, Any]:
        """The data-position state of the restored step (sampler
        position + the master's shard checkpoint — the same JSON the
        Orbax data item carries). Local manifest first, then any remote
        donor that served us, then the committed Orbax data item; a
        genuinely unrecoverable position is LOUD (flight event +
        warning) — a silently reset sampler would replay seen data."""
        if self._cache is not None:
            manifest = load_stage_manifest(self._cache.directory, step)
            if manifest is not None:
                return dict(manifest.get("data_state", {}))
        for addr in donor_bytes:
            if addr == "local":
                continue
            manifest = fetch_manifest(addr, step=step)
            if manifest is not None and \
                    int(manifest.get("step", -1)) == step:
                return dict(manifest.get("data_state", {}))
        if checkpointer is not None:
            data = checkpointer.restore_data_state(step)
            if data is not None:
                return data
        obs.get_flight_recorder().record_event(
            "peer_restore_no_data_state", step=step)
        logger.warning(
            "peer restore: no data-position state recoverable for step "
            "%d (no donor manifest, step not in storage) — the sampler "
            "position resets", step)
        return {}

    def _assemble(self, abstract_state: Any,
                  abstract_by_key: Dict[str, Any],
                  got: Dict[str, bytes],
                  overlay: Optional[Dict[str, Any]] = None) -> Any:
        """Fetched bytes (+ optional Orbax overlay leaves) → device
        arrays in the abstract state's shardings."""
        import jax

        from dlrover_tpu.parallel.sharding import sharded_from_host

        host_leaves: Dict[str, Any] = {}
        for key, leaf in abstract_by_key.items():
            if key in got:
                # an OWNED, writable, numpy-aligned copy — never a view
                # over the fetched bytes: jax's CPU path zero-copy
                # aliases host buffers, and the train step's donated
                # state update would then write into the (read-only,
                # unaligned) bytes payload — observed as glibc heap
                # corruption a few steps after restore. pop() drops the
                # raw bytes as we go so peak host memory stays ~2x the
                # state, not 3x.
                host_leaves[key] = np.frombuffer(
                    got.pop(key), dtype=leaf.dtype
                ).reshape(leaf.shape).copy()
            else:
                host_leaves[key] = (overlay or {})[key]
        flat, treedef = jax.tree_util.tree_flatten_with_path(
            abstract_state)
        ordered = [host_leaves[jax.tree_util.keystr(path)]
                   for path, _ in flat]
        host_tree = jax.tree_util.tree_unflatten(treedef, ordered)
        return sharded_from_host(host_tree, abstract_state)

    def _finish_mixed(self, abstract_state, abstract_by_key, got,
                      missing, step, data_state, checkpointer,
                      donor_bytes, timings):
        """Shard-wise degradation: the shards no surviving replica holds
        come from Orbax at the SAME step (mixing steps would assemble a
        state that never existed). Loud by design — this is the failure
        domain doing damage, not business as usual."""
        recorder = obs.get_flight_recorder()
        if checkpointer is None or \
                step not in set(checkpointer.all_steps() or ()):
            recorder.record_event(
                "peer_restore_fallback", step=step, source="orbax",
                missing=len(missing), sample=missing[:5],
                reason="staged step not committed to storage")
            logger.error(
                "peer restore: %d shard(s) unavailable from any "
                "surviving peer and step %d is not in storage — "
                "falling back to the full Orbax restore", len(missing),
                step)
            return None
        logger.error(
            "peer restore DEGRADED: no surviving replica for %d "
            "shard(s) (e.g. %s) — reading them from Orbax step %d",
            len(missing), ", ".join(missing[:3]), step)
        recorder.record_event(
            "peer_restore_fallback", step=step, source="mixed",
            missing=len(missing), sample=missing[:5],
            reason="no surviving replica; shard-wise Orbax read")
        t0 = time.monotonic()
        with obs.span("restore_tensor_read",
                      {"step": step, "mixed": True}):
            orbax_state, orbax_data, _ = checkpointer.restore_step(
                step, abstract_state)
        timings["orbax_read_s"] = round(time.monotonic() - t0, 2)
        overlay = {key: leaf
                   for key, leaf in shard_items(orbax_state)
                   if key in missing}
        if not data_state:
            data_state = orbax_data
        transferred = sum(len(d) for d in got.values())
        state = self._assemble(abstract_state, abstract_by_key, got,
                               overlay=overlay)
        self._record(step, "mixed", donor_bytes, missing=len(missing),
                     total_bytes=transferred,
                     transfer_s=timings.get("peer_transfer_s", 0.0))
        return state, data_state, step, "mixed"

    def _record(self, step: int, source: str,
                donor_bytes: Dict[str, int], missing: int,
                total_bytes: int, transfer_s: float) -> None:
        registry = obs.get_registry()
        registry.counter(
            "dlrover_tpu_restore_source_total",
            "Elastic restores by state source",
            labelnames=("source",)).labels(source=source).inc()
        registry.gauge(
            "dlrover_tpu_checkpoint_restore_bytes",
            "Bytes read by the last checkpoint restore",
            labelnames=("source",)).labels(source="peer").set(
            float(total_bytes))
        if transfer_s > 0 and total_bytes > 0:
            registry.gauge(
                "dlrover_tpu_checkpoint_restore_bandwidth_mbps",
                "Effective bandwidth of the last restore's "
                "tensor-transfer phase",
                labelnames=("source",)).labels(source="peer").set(
                round(total_bytes / (1 << 20) / transfer_s, 2))
        obs.get_flight_recorder().record_event(
            "peer_restore", step=step, source=source,
            bytes=total_bytes, missing=missing,
            donors={str(k): v for k, v in donor_bytes.items()})
        logger.info(
            "peer restore at step %d: source=%s %.1f MiB from %d "
            "donor(s) in %.2fs%s", step, source, total_bytes / (1 << 20),
            len(donor_bytes), transfer_s,
            f" ({missing} shard(s) via Orbax)" if missing else "")
