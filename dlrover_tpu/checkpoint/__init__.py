"""Flash checkpoint: async sharded save/restore with reshard-on-restore."""

from dlrover_tpu.checkpoint.flash_checkpoint import (  # noqa: F401
    FlashCheckpointer,
    abstract_state_for,
)
