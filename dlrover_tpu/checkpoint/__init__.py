"""Flash checkpoint: async sharded save/restore with reshard-on-restore,
plus the peer-to-peer restore path (surviving hosts donate state)."""

from dlrover_tpu.checkpoint.flash_checkpoint import (  # noqa: F401
    FlashCheckpointer,
    abstract_state_for,
)
from dlrover_tpu.checkpoint.peer_restore import (  # noqa: F401
    PeerDonorServer,
    PeerRestorer,
    PeerStateStore,
)
from dlrover_tpu.checkpoint.quantized import (  # noqa: F401
    abstract_encoded,
    decode_tree,
    encode_tree,
    encoded_nbytes,
)
