"""Flash checkpoint: async sharded save/restore with reshard-on-restore."""

from dlrover_tpu.checkpoint.flash_checkpoint import (  # noqa: F401
    FlashCheckpointer,
    abstract_state_for,
)
from dlrover_tpu.checkpoint.quantized import (  # noqa: F401
    abstract_encoded,
    decode_tree,
    encode_tree,
    encoded_nbytes,
)
