"""`dlrover-tpu-run` — the elastic launcher CLI.

Capability parity: dlrover/trainer/torch/elastic_run.py (the `dlrover-run`
torchrun superset: `--nnodes min:max`, `--standalone` auto-spawning a local
master :184-209, `--network-check`, `--max-restarts`) re-designed for JAX:
one agent per TPU host spawns ONE JAX process owning all local chips.

Usage:
    dlrover-tpu-run --standalone train.py --lr 3e-4
    dlrover-tpu-run --nnodes 2:4 --node-rank $RANK \
        --master-addr $DLROVER_TPU_MASTER_ADDR train.py
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional, Tuple

from dlrover_tpu.agent.elastic_agent import ElasticAgent, WorkerSpec
from dlrover_tpu.agent.master_client import MasterClient
from dlrover_tpu.common.constants import DefaultValues, NodeEnv
from dlrover_tpu.common.log import default_logger as logger


def _parse_nnodes(value: str) -> Tuple[int, int]:
    if ":" in value:
        lo, hi = value.split(":", 1)
        return int(lo), int(hi)
    n = int(value)
    return n, n


def parse_args(argv: Optional[List[str]] = None) -> argparse.Namespace:
    parser = argparse.ArgumentParser(
        "dlrover-tpu-run", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument("--nnodes", default="1",
                        help="node count, fixed `N` or elastic `MIN:MAX`")
    parser.add_argument("--node-rank", type=int,
                        default=int(os.getenv(NodeEnv.NODE_RANK, "0")))
    parser.add_argument("--slice-id", type=int,
                        default=int(os.getenv(NodeEnv.SLICE_ID, "-1")),
                        help="ICI slice this host belongs to "
                             "(multi-slice hierarchical DP; the slice "
                             "is the failure domain). -1 = single-"
                             "slice job")
    parser.add_argument("--master-addr",
                        default=os.getenv(NodeEnv.MASTER_ADDR, ""))
    parser.add_argument("--standalone", action="store_true",
                        help="run a local in-process master (single host)")
    parser.add_argument("--max-restarts", type=int,
                        default=DefaultValues.MAX_RELAUNCH)
    parser.add_argument("--monitor-interval", type=float,
                        default=DefaultValues.MONITOR_INTERVAL_S)
    parser.add_argument("--devices-per-node", type=int, default=0,
                        help="local chip count (0 = autodetect lazily)")
    parser.add_argument("--network-check", action="store_true",
                        help="run the ICI/DCN probe before training "
                             "(reference: dlrover-run --network-check)")
    parser.add_argument("--exclude-straggler", action="store_true",
                        help="exit instead of training when this node is "
                             "flagged as a straggler by the probe")
    parser.add_argument("--node-unit", type=int, default=1)
    parser.add_argument("--no-python", action="store_true",
                        help="run the entrypoint as a raw command")
    parser.add_argument("entrypoint", help="training script (or command)")
    parser.add_argument("entry_args", nargs=argparse.REMAINDER)
    return parser.parse_args(argv)


def _detect_devices() -> int:
    env = os.getenv(NodeEnv.DEVICES_PER_NODE)
    if env:
        return int(env)
    # Detect in a short-lived subprocess: importing jax here would
    # initialize the TPU runtime in the AGENT process and hold the chips,
    # so the spawned training process could never acquire them.
    import subprocess

    try:
        out = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.local_device_count())"],
            capture_output=True, text=True, timeout=120,
        )
        return int(out.stdout.strip().splitlines()[-1])
    except Exception:
        return 1


def run(args: argparse.Namespace) -> int:
    min_nodes, max_nodes = _parse_nnodes(args.nnodes)
    master = None
    master_addr = args.master_addr
    if args.standalone:
        from dlrover_tpu.master.job_master import JobMaster

        master = JobMaster(min_nodes=min_nodes, max_nodes=max_nodes,
                           node_unit=args.node_unit, host="127.0.0.1")
        master.prepare()
        master_addr = master.addr
        logger.info("standalone master at %s", master_addr)
    if not master_addr:
        raise SystemExit(
            "--master-addr (or DLROVER_TPU_MASTER_ADDR) is required unless "
            "--standalone"
        )

    entrypoint = list(args.entry_args)
    if args.no_python:
        entrypoint.insert(0, args.entrypoint)
    else:
        entrypoint = [sys.executable, args.entrypoint] + entrypoint

    node_type = os.environ.get(NodeEnv.NODE_TYPE, "worker")
    # NODE_ID diverges from rank after a relaunch (replacement nodes get a
    # fresh id); heartbeats/failures must carry the id the master tracks
    node_id = int(os.environ.get(NodeEnv.NODE_ID, str(args.node_rank)))
    client = MasterClient(master_addr, node_id=node_id,
                          node_rank=args.node_rank, node_type=node_type,
                          slice_id=args.slice_id)
    devices = args.devices_per_node or _detect_devices()
    spec = WorkerSpec(
        entrypoint=entrypoint,
        devices_per_node=devices,
        max_restarts=args.max_restarts,
        monitor_interval_s=args.monitor_interval,
    )
    agent = ElasticAgent(client, spec)
    try:
        if args.network_check:
            from dlrover_tpu.diagnostics.network_check import (
                run_network_check,
            )

            ok = run_network_check(
                client, devices, exclude_straggler=args.exclude_straggler
            )
            if not ok:
                logger.error("network check verdict: this node must not "
                             "join training")
                return 3
        return agent.run()
    finally:
        agent.shutdown()
        client.close()
        if master is not None:
            master.stop()


def main(argv: Optional[List[str]] = None) -> int:
    return run(parse_args(argv))


if __name__ == "__main__":
    raise SystemExit(main())
