"""dlrover_tpu: a TPU-native elastic-training framework.

A brand-new framework with the capabilities of DLRover (reference:
``dlrover/python`` + ``atorch``), re-designed TPU-first:

- Control plane: a centralized per-job **master** (rendezvous, dynamic data
  sharding, health/straggler diagnostics, auto-scaling) with thin node
  **agents** — the same load-bearing design as the reference
  (``dlrover/python/master/dist_master.py``), re-implemented for JAX jobs.
- Data plane: pure JAX — a ``jax.sharding.Mesh`` of named axes
  (``data``/``fsdp``/``tensor``/``sequence``/``expert``/``pipe``) replaces the
  reference's torch process-group zoo; collectives ride ICI/DCN via XLA.
- Acceleration: ``auto_accelerate`` lowers a named strategy onto the mesh
  (reference: ``atorch/auto/accelerate.py``), with Pallas kernels for the hot
  ops (flash attention, fused norms, quantization).
- Elasticity: master-backed rendezvous re-forms the world; training restarts
  re-lower to the new mesh and restore resharded checkpoints.
"""

__version__ = "0.1.0"
