#!/usr/bin/env python
"""Control-plane bench: joins/s, KV ops/s, time-to-reform — sharded vs
single-lock, at ~1k simulated ranks.

The sharded control plane (master/rendezvous_shards.py) claims two
things ROADMAP item 5 needs measured, not asserted:

1. **joins/s scales with slice count.** The single-lock manager's
   slice-ready check scans the WHOLE fleet's waiting list under ONE lock
   for every poll — O(N) work serialized fleet-wide, O(N²) for a full
   fleet formation. A shard scans only its slice (O(N/S)), under its own
   lock. The bench forms a full fleet through the real join/poll/cut
   protocol with a thread pool of simulated agents, both managers, same
   driver.
2. **Per-slice time-to-reform stays flat as the fleet grows.** After a
   member death, the victim slice's re-join → cut latency is measured
   while every OTHER rank keeps up its steady-state waiting-num poll
   (the load that makes a single lock a bottleneck), across slice
   counts.

Plus the coordination tier's substrate numbers: KV set/get ops/s and the
lock-free read's p99 while writers churn the condition variable.

Usage:
    python bench_controlplane.py                  # full (1024 ranks)
    python bench_controlplane.py --smoke          # CI-sized, < ~60 s
    python bench_controlplane.py --json out.json

The smoke run is exercised as a slow test in tests/test_controlplane.py
so these numbers land in CI artifacts.
"""

from __future__ import annotations

import argparse
import json
import statistics
import sys
import threading
import time
from concurrent.futures import ThreadPoolExecutor

REPO = __file__.rsplit("/", 1)[0]
sys.path.insert(0, REPO)

from dlrover_tpu.master.kv_store import KVStoreService  # noqa: E402
from dlrover_tpu.master.rendezvous import (  # noqa: E402
    ElasticTrainingRendezvousManager,
    RendezvousParameters,
)
from dlrover_tpu.master.rendezvous_shards import (  # noqa: E402
    ShardedRendezvousManager,
)


def _build_manager(kind: str, ranks: int):
    params = RendezvousParameters(min_nodes=1, max_nodes=ranks,
                                  wait_new_node_s=30.0)
    if kind == "sharded":
        return ShardedRendezvousManager(params)
    return ElasticTrainingRendezvousManager(params)


def _preregister(mgr, ranks: int, slices: int) -> None:
    """Teach the registry every rank's slice and aliveness up front so
    each slice's round cuts exactly once, when its LAST member joins
    (no transient partial worlds — same discipline as the replan
    acceptance test)."""
    for rank in range(ranks):
        mgr.record_slice(rank, rank % slices)
        mgr.add_alive_node(rank)


def _form_fleet(mgr, ranks: int, slices: int, threads: int) -> float:
    """Drive the real protocol: every rank joins, then polls until it
    holds a cut world. Each pool thread simulates a COHORT of agents
    (join them all, then round-robin their polls) so a slice's cut can
    never starve on pool capacity. Returns the fleet's wall seconds."""
    deadline = time.monotonic() + 600.0

    def cohort(chunk) -> None:
        for rank in chunk:
            mgr.join_rendezvous(rank, 1, slice_id=rank % slices)
        pending = set(chunk)
        while pending:
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"ranks {sorted(pending)[:4]}... never saw a cut "
                    f"world")
            for rank in list(pending):
                _, _, world = mgr.get_comm_world(rank)
                if world and rank in world:
                    pending.discard(rank)
            time.sleep(0.0005)

    chunks = [list(range(ranks))[i::threads] for i in range(threads)]
    start = time.monotonic()
    with ThreadPoolExecutor(max_workers=threads) as pool:
        for future in [pool.submit(cohort, c) for c in chunks if c]:
            future.result()
    return time.monotonic() - start


def bench_joins(ranks: int, slices: int, threads: int) -> dict:
    out: dict = {}
    for kind in ("single_lock", "sharded"):
        mgr = _build_manager(kind, ranks)
        _preregister(mgr, ranks, slices)
        wall = _form_fleet(mgr, ranks, slices, threads)
        out[kind] = {"wall_s": round(wall, 4),
                     "joins_per_s": round(ranks / wall, 1)}
        assert len(mgr.latest_world) == ranks, (
            f"{kind}: fleet never fully formed "
            f"({len(mgr.latest_world)}/{ranks})")
    out["speedup"] = round(out["sharded"]["joins_per_s"]
                           / out["single_lock"]["joins_per_s"], 2)
    return out


def bench_reform(ranks: int, slice_counts, threads: int) -> dict:
    """Victim-slice re-form latency under steady-state poll load, per
    slice count. The victim is always slice 0; every surviving rank
    polls num_nodes_waiting in the background (the monitor-tick load)."""
    out: dict = {}
    for kind in ("single_lock", "sharded"):
        per_slices = {}
        for slices in slice_counts:
            mgr = _build_manager(kind, ranks)
            _preregister(mgr, ranks, slices)
            _form_fleet(mgr, ranks, slices, threads)
            victims = [r for r in range(ranks) if r % slices == 0]
            stop = threading.Event()

            def poller(rank: int) -> None:
                while not stop.is_set():
                    mgr.num_nodes_waiting(rank)
                    time.sleep(0.001)

            pollers = [threading.Thread(target=poller, args=(r,),
                                        daemon=True)
                       for r in range(ranks) if r % slices != 0]
            for thread in pollers:
                thread.start()
            try:
                start = time.monotonic()
                mgr.remove_alive_node(victims[0])
                for rank in victims:
                    mgr.join_rendezvous(rank, 1, slice_id=0)
                while True:
                    _, _, world = mgr.get_comm_world(victims[0])
                    if world and set(world) == set(victims):
                        break
                    if time.monotonic() - start > 120.0:
                        raise TimeoutError(
                            f"{kind}/{slices}: slice never re-formed")
                    time.sleep(0.0005)
                per_slices[str(slices)] = round(
                    (time.monotonic() - start) * 1000.0, 2)
            finally:
                stop.set()
                for thread in pollers:
                    thread.join(timeout=2.0)
        out[kind] = per_slices
    return out


def bench_kv(ops: int, threads: int) -> dict:
    """The coordination substrate: hot-key set/get throughput and the
    lock-free read's p99 while writers churn the condition variable."""
    kv = KVStoreService()
    payload = b"x" * 4096

    def setter(worker: int) -> int:
        for i in range(ops):
            kv.set(f"dcn/g0/grads/{worker}", payload)
        return ops

    start = time.monotonic()
    with ThreadPoolExecutor(max_workers=threads) as pool:
        total = sum(pool.map(setter, range(threads)))
    set_wall = time.monotonic() - start

    stop = threading.Event()

    def churn() -> None:
        i = 0
        while not stop.is_set():
            kv.set(f"dcn/g0/grads/{i % threads}", payload)
            i += 1

    churner = threading.Thread(target=churn, daemon=True)
    churner.start()
    latencies = []
    start = time.monotonic()
    reads = 0
    try:
        for i in range(ops * threads):
            t0 = time.perf_counter()
            kv.get(f"dcn/g0/grads/{i % threads}")
            latencies.append(time.perf_counter() - t0)
            reads += 1
    finally:
        stop.set()
        churner.join(timeout=2.0)
    get_wall = time.monotonic() - start
    latencies.sort()
    p99 = latencies[int(0.99 * (len(latencies) - 1))]
    return {
        "set_ops_per_s": round(total / set_wall, 1),
        "get_ops_per_s": round(reads / get_wall, 1),
        "get_p50_us": round(
            statistics.median(latencies) * 1e6, 2),
        "get_p99_us": round(p99 * 1e6, 2),
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser("control-plane bench")
    parser.add_argument("--ranks", type=int, default=1024,
                        help="simulated fleet size (>= 1k for the "
                             "headline numbers)")
    parser.add_argument("--slices", type=int, default=16)
    parser.add_argument("--threads", type=int, default=32,
                        help="simulated-agent thread pool")
    parser.add_argument("--kv-ops", type=int, default=2000,
                        help="kv ops per thread")
    parser.add_argument("--smoke", action="store_true",
                        help="CI-sized run (fewer ranks/ops)")
    parser.add_argument("--json", default="",
                        help="also write the result JSON here")
    ns = parser.parse_args(argv)
    if ns.smoke:
        ns.ranks = min(ns.ranks, 192)
        ns.slices = min(ns.slices, 8)
        ns.threads = min(ns.threads, 16)
        ns.kv_ops = min(ns.kv_ops, 300)
    reform_slices = sorted({2, max(2, ns.slices // 2), ns.slices})

    result = {
        "ranks": ns.ranks, "slices": ns.slices, "threads": ns.threads,
        "smoke": bool(ns.smoke),
    }
    print(f"# joins/s: {ns.ranks} ranks x {ns.slices} slices, "
          f"{ns.threads} agent threads", flush=True)
    result["joins"] = bench_joins(ns.ranks, ns.slices, ns.threads)
    print(json.dumps(result["joins"], indent=2), flush=True)
    print(f"# per-slice time-to-reform over slice counts "
          f"{reform_slices}", flush=True)
    result["reform_ms"] = bench_reform(ns.ranks, reform_slices,
                                       ns.threads)
    print(json.dumps(result["reform_ms"], indent=2), flush=True)
    print("# kv substrate", flush=True)
    result["kv"] = bench_kv(ns.kv_ops, min(8, ns.threads))
    print(json.dumps(result["kv"], indent=2), flush=True)

    print("\n== control-plane bench ==")
    joins = result["joins"]
    print(f"joins/s: single-lock {joins['single_lock']['joins_per_s']}"
          f" -> sharded {joins['sharded']['joins_per_s']}  "
          f"({joins['speedup']}x)")
    for kind in ("single_lock", "sharded"):
        row = ", ".join(f"S={s}: {ms}ms"
                        for s, ms in result["reform_ms"][kind].items())
        print(f"reform[{kind}]: {row}")
    kv = result["kv"]
    print(f"kv: {kv['set_ops_per_s']} set/s, "
          f"{kv['get_ops_per_s']} get/s, get p99 {kv['get_p99_us']}us")
    if ns.json:
        with open(ns.json, "w") as f:
            json.dump(result, f, indent=2)
        print(f"json -> {ns.json}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
