"""Online re-plan benchmark: time-to-first-step after a resize, replan
path vs the forced checkpoint (Orbax) round-trip.

The story being measured (ISSUE 11 / ROADMAP item 1): a world resize to
a divisor-unfriendly size used to force the same DP shape (crash on a
non-divisor batch) or a full checkpoint round-trip. The planner
(parallel/planner.py) now picks a DP×TP×PP mesh for ANY world size at
the rendezvous cut, and the live state migrates from the host-RAM peer
cache under the NEW sharding — no storage round-trip.

This bench runs the real stack in one process against a standalone
JobMaster: a world of N chips trains past a committed checkpoint +
peer stage, then resizes to N−1 and N+1 (both divisor-unfriendly for
the batch). For each resize it clocks loop rebuild → plan → migrate →
first completed step, twice:

- ``replan``       — the plan rides the join/RPC, state migrates from
                     the peer cache under the new sharding,
- ``forced_orbax`` — peer restore disabled: the same re-plan but the
                     state takes the checkpoint round-trip.

Prints ONE JSON line:
    {"metric": "replan_time_to_first_step_seconds", "value": S, ...,
     "scenarios": {"shrink": {...}, "grow": {...}}}

with per-scenario phase breakdowns and phase_coverage (exclusive
phases must explain the headline number).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

GLOBAL_BATCH = 10          # divisor-unfriendly for both 4 and 6 chips
SEQ_LEN = 32
BASE_DEVICES = 5
SAVE_INTERVAL = 1
WARM_STEPS = 3


def _ensure_cpu_devices(n: int) -> None:
    """Before jax imports: enough virtual CPU devices for the largest
    world this bench builds (no-op on real accelerators)."""
    if os.environ.get("JAX_PLATFORMS", "") != "cpu" and \
            "JAX_PLATFORMS" not in os.environ:
        os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={n}"
        ).strip()


def _batches(vocab: int, batch: int, seq: int, n: int, seed: int):
    import numpy as np

    rng = np.random.default_rng(seed)
    for _ in range(n):
        tokens = rng.integers(0, vocab, (batch, seq), dtype=np.int64)
        yield tokens, tokens


def _resize_once(model, tx, loss_fn, config, client, devices,
                 target: int, forced_orbax: bool) -> dict:
    """One clocked resize: re-join with the target chip count (ONE
    rendezvous round — the join stamps the plan), rebuild the loop,
    restore/migrate, run the first step."""
    import jax

    from dlrover_tpu.trainer.elastic_loop import ElasticTrainLoop

    t0 = time.perf_counter()
    client.join_rendezvous(target)
    while True:
        _, _, world = client.get_comm_world()
        if world:
            break
        time.sleep(0.01)
    t_join = time.perf_counter()
    loop = ElasticTrainLoop(model, tx, loss_fn, config,
                            master_client=client,
                            devices=devices[:target])
    if forced_orbax:
        # the comparison leg: same re-plan, but the state takes the
        # checkpoint round-trip (staging stays on so later scenarios
        # keep a live peer cache)
        loop._peer_restorer = None
    t_build = time.perf_counter()
    try:
        state, start = loop.restore_or_init(jax.random.PRNGKey(0))
        t_restore = time.perf_counter()
        state, metrics = loop.run(
            state,
            _batches(256, config.global_batch, config.seq_len, 1,
                     seed=start),
            start_step=start)
        t_first = time.perf_counter()
        timings = dict(loop.last_restore_timings)
        breakdown = {
            "rendezvous_s": round(t_join - t0, 3),
            "loop_build_s": round(t_build - t_join, 3),
            "restore_s": round(t_restore - t_build, 3),
            "first_step_s": round(t_first - t_restore, 3),
        }
        elapsed = t_first - t0
        phase_sum = sum(breakdown.values())
        result = {
            "time_to_first_step_s": round(elapsed, 3),
            "restored_step": start,
            "stepped_to": int(metrics.get("step", -1)),
            "restore_source": loop.last_restore_source,
            "replan_applied": loop._replan_applied,
            "mesh": dict(loop.mesh.shape),
            "global_batch": loop.global_batch,
            "breakdown": breakdown,
            "restore_timings": {k: v for k, v in timings.items()
                                if isinstance(v, (int, float))},
            "phase_sum_s": round(phase_sum, 3),
            "phase_coverage": round(phase_sum / elapsed, 3)
            if elapsed > 0 else 0.0,
        }
        return result
    finally:
        loop.close()


def run_bench() -> dict:
    import jax
    import optax

    from dlrover_tpu.agent.master_client import MasterClient
    from dlrover_tpu.common.constants import NodeEnv
    from dlrover_tpu.master.job_master import JobMaster
    from dlrover_tpu.models.llama import (
        Llama,
        LlamaConfig,
        cross_entropy_loss,
    )
    from dlrover_tpu.trainer.elastic_loop import (
        ElasticTrainLoop,
        TrainLoopConfig,
    )

    workdir = tempfile.mkdtemp(prefix="bench-replan-")
    os.environ[NodeEnv.PEER_CACHE_DIR] = os.path.join(workdir, "cache")

    devices = jax.devices()
    if len(devices) < BASE_DEVICES + 1:
        raise SystemExit(
            f"need {BASE_DEVICES + 1} devices, have {len(devices)} "
            f"(CPU: the bench exports "
            f"xla_force_host_platform_device_count itself — run it "
            f"directly, not under an inherited XLA_FLAGS)")

    cfg = LlamaConfig.tiny(attn_impl="reference", norm_impl="reference")
    model = Llama(cfg)
    tx = optax.adamw(3e-4)
    config = TrainLoopConfig(
        global_batch=GLOBAL_BATCH, seq_len=SEQ_LEN,
        checkpoint_dir=os.path.join(workdir, "ckpt"),
        save_interval_steps=SAVE_INTERVAL,
        report_interval_steps=1,
    )

    master = JobMaster(min_nodes=1, max_nodes=1, host="127.0.0.1")
    master.prepare()
    client = MasterClient(master.addr, node_id=0, node_rank=0)
    try:
        # phase 0: the base world trains past a committed checkpoint +
        # peer stage (what the resize will migrate from)
        client.join_rendezvous(BASE_DEVICES)
        loop = ElasticTrainLoop(model, tx, cross_entropy_loss, config,
                                master_client=client,
                                devices=devices[:BASE_DEVICES])
        state, start = loop.restore_or_init(jax.random.PRNGKey(0))
        state, metrics = loop.run(
            state, _batches(cfg.vocab_size, GLOBAL_BATCH, SEQ_LEN,
                            WARM_STEPS, seed=0),
            start_step=start)
        base_step = int(metrics["step"])
        loop.close()

        scenarios = {}
        for name, target in (("shrink", BASE_DEVICES - 1),
                             ("grow", BASE_DEVICES + 1)):
            scenarios[name] = {
                "target_devices": target,
                "replan": _resize_once(
                    model, tx, cross_entropy_loss, config, client,
                    devices, target, forced_orbax=False),
                "forced_orbax": _resize_once(
                    model, tx, cross_entropy_loss, config, client,
                    devices, target, forced_orbax=True),
            }
        headline = scenarios["shrink"]["replan"][
            "time_to_first_step_s"]
        snap = master.goodput_ledger.snapshot()
        # the prediction<->measurement loop, benchmarked not asserted:
        # every plan this run stamped with the planner's predicted step
        # time beside the steady-state measured one (parallel/
        # calibration.py; >= 2 distinct mesh shapes — base, shrink,
        # grow — each with its own predicted-vs-measured row)
        calibration = master.plan_calibration.table()
        # the fleet's critical-path attribution over everything this
        # run traced (master/steptrace.py): single-slice here, so the
        # interesting numbers are the dominant gating phase and that
        # the cross-slice wait is honestly ~0
        steptrace = master.steptrace.summary()
        return {
            "metric": "replan_time_to_first_step_seconds",
            "value": headline,
            "unit": (f"s (join -> plan -> migrate -> rebuild -> first "
                     f"step; {BASE_DEVICES}->{BASE_DEVICES - 1} chips, "
                     f"batch {GLOBAL_BATCH})"),
            "base_devices": BASE_DEVICES,
            "base_step": base_step,
            "scenarios": scenarios,
            "replans_priced": snap.get("replans", []),
            "goodput_fraction": snap.get("goodput_fraction", 0.0),
            "calibration": calibration,
            "axis_discounts": master.plan_calibration.axis_discounts(
                min_samples=1),
            "critical_path": {
                "traced_steps": steptrace.get("steps", 0),
                "dominant_gating_rank": steptrace.get(
                    "dominant_gating_rank", -1),
                "dominant_gating_phase": steptrace.get(
                    "dominant_gating_phase", ""),
                "cross_slice_wait_fraction": steptrace.get(
                    "cross_slice_wait_fraction", -1.0),
            },
            "workdir": workdir,
        }
    finally:
        client.close()
        master.stop()


def main() -> int:
    parser = argparse.ArgumentParser("bench_replan", description=__doc__)
    parser.parse_args()
    result = run_bench()
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    _ensure_cpu_devices(BASE_DEVICES + 1)
    raise SystemExit(main())
